"""Per-statement device round-trip accounting.

On the tunneled chip every program dispatch / host->device transfer
costs the dispatch floor (~80ms RTT), so `n_dispatch`/`n_transfer` in
query history stats are the wall-time budget made auditable (≈ the
reference's per-query druid-time vs total-time split in
DruidQueryHistory, DruidQueryExecutionMetric.scala:26-80).
"""

import pytest

import spark_druid_olap_tpu as sdot

from conftest import make_sales_df


@pytest.fixture(scope="module")
def ctx():
    c = sdot.Context()
    c.ingest_dataframe("sales", make_sales_df(), time_column="ts",
                       target_rows=4096)
    return c


def _stats(ctx):
    return ctx.history.entries()[-1].stats


def test_agg_query_counts_dispatches(ctx):
    ctx.sql("select region, sum(qty) as s from sales group by region")
    st = _stats(ctx)
    assert st["mode"] == "engine"
    assert st["n_dispatch"] >= 1
    # first run uploads the scan columns
    assert st["n_transfer"] >= 1


def test_warm_query_reuses_device_arrays(ctx):
    q = "select region, sum(qty) as s2 from sales group by region"
    ctx.sql(q)
    ctx.sql(q)
    st = _stats(ctx)
    # same columns already resident: no new transfers, same dispatch count
    assert st["n_transfer"] == 0
    assert st["n_dispatch"] >= 1


def test_counts_accumulate_across_subqueries(ctx):
    ctx.sql("select region, sum(qty) as s from sales "
            "where qty > (select avg(qty) from sales) group by region")
    st = _stats(ctx)
    assert st["mode"] == "engine"
    # subquery + outer each dispatch at least once (subquery may be
    # result-cached from a prior test run in this module, so >= 1 total)
    assert st["n_dispatch"] >= 1


def test_counters_are_monotone_and_thread_local(ctx):
    c0 = list(ctx.engine.dispatch_counts)
    ctx.sql("select count(*) as n from sales")
    c1 = ctx.engine.dispatch_counts
    assert c1[0] >= c0[0]
    assert c1[1] >= c0[1]


def test_cached_program_concurrent_failure_recovery(ctx):
    """If a compile owner raises, a waiter claims ownership and retries
    (per-signature compile events must not deadlock or cache garbage)."""
    import threading
    eng = ctx.engine
    sig = ("test-prog", "failure-recovery")
    calls = {"n": 0}
    lock = threading.Lock()

    def flaky_build():
        with lock:
            calls["n"] += 1
            mine = calls["n"]
        if mine == 1:
            raise RuntimeError("first build fails")
        return "compiled"

    results, errors = [], []

    def worker():
        try:
            results.append(eng._cached_program(sig, flaky_build))
        except RuntimeError as e:
            errors.append(e)

    ts = [threading.Thread(target=worker) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in ts), "deadlocked"
    # exactly one failure propagated to the first owner; everyone else
    # got the successfully-built program
    assert len(errors) == 1
    assert results == ["compiled"] * 3
    assert eng._programs.get(sig) == "compiled"
    eng._programs.pop(sig, None)
