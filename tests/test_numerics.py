"""TPU-dtype exactness tests.

These run with x64 DISABLED, which makes the CPU backend canonicalize to
i32/f32 — the same dtype environment as a real TPU (where f64 is unsupported
and i64 emulated). Every integer aggregate must then be EXACT via the lane /
limb / i32 routes (groupby.plan_route), not merely float-close: Druid's
aggregators are exact longs (reference ``DruidQuerySpec.scala:283-377``).

Covers the round-1 verdict's failure cases: int columns with values > 2^24
(min/max/anyvalue would round in f32), sums > 2^32 (overflow i32, round in
f32), on both the MXU one-hot-matmul path and the scatter path, single-chip
and sharded over the virtual 8-device mesh (limb psum + per-chip ff host
combine).
"""

import numpy as np
import pandas as pd
import pytest
import jax

from spark_druid_olap_tpu.segment.ingest import ingest_dataframe
from spark_druid_olap_tpu.segment.store import SegmentStore
from spark_druid_olap_tpu.parallel.executor import QueryEngine
from spark_druid_olap_tpu.parallel.mesh import make_mesh
from spark_druid_olap_tpu.utils.config import Config
from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ir.spec import (
    AggregationSpec,
    DimensionSpec,
    GroupByQuerySpec,
)

N_ROWS = 60_000


@pytest.fixture(scope="module")
def no_x64():
    """TPU dtype environment: i32/f32 canonical types."""
    jax.config.update("jax_enable_x64", False)
    yield
    jax.config.update("jax_enable_x64", True)


@pytest.fixture(scope="module")
def big_df():
    r = np.random.default_rng(11)
    ts = (np.datetime64("2019-01-01")
          + r.integers(0, 365, N_ROWS).astype("timedelta64[D]"))
    return pd.DataFrame({
        "ts": ts.astype("datetime64[ns]"),
        "g": r.choice(["a", "b", "c", "d"], N_ROWS),
        # values straddling 2^24 (f32 integer-exactness cliff) and
        # well past 2^20 so the 60k-row sums exceed 2^32
        "big": (r.integers(0, 1 << 30, N_ROWS)
                + (1 << 24)).astype(np.int64),
        "sgn": r.integers(-(1 << 26), 1 << 26, N_ROWS).astype(np.int64),
        "small": r.integers(0, 100, N_ROWS).astype(np.int64),
        "price": np.round(r.uniform(1e3, 1e5, N_ROWS), 2),
    })


@pytest.fixture(scope="module")
def big_store(big_df):
    ds = ingest_dataframe("big", big_df, time_column="ts",
                          target_rows=8192)
    st = SegmentStore()
    st.register(ds)
    return st


def _spec(**kw):
    base = dict(
        datasource="big",
        dimensions=(DimensionSpec("g", "g"),),
        aggregations=(
            AggregationSpec("longsum", "s_big", field="big"),
            AggregationSpec("longsum", "s_sgn", field="sgn"),
            AggregationSpec("longsum", "s_small", field="small"),
            AggregationSpec("longmin", "mn_big", field="big"),
            AggregationSpec("longmax", "mx_big", field="big"),
            AggregationSpec("longmin", "mn_sgn", field="sgn"),
            AggregationSpec("count", "n"),
            AggregationSpec("doublesum", "s_price", field="price"),
        ))
    base.update(kw)
    return GroupByQuerySpec(**base)


def _oracle(df):
    g = df.groupby("g")
    return pd.DataFrame({
        "s_big": g["big"].sum(),
        "s_sgn": g["sgn"].sum(),
        "s_small": g["small"].sum(),
        "mn_big": g["big"].min(),
        "mx_big": g["big"].max(),
        "mn_sgn": g["sgn"].min(),
        "n": g.size(),
        "s_price": g["price"].sum(),
    }).reset_index()


def _check_exact(r, big_df):
    got = r.to_pandas().sort_values("g").reset_index(drop=True)
    want = _oracle(big_df).sort_values("g").reset_index(drop=True)
    for c in ("s_big", "s_sgn", "s_small", "mn_big", "mx_big", "mn_sgn",
              "n"):
        np.testing.assert_array_equal(
            got[c].to_numpy().astype(np.int64), want[c].to_numpy(),
            err_msg=f"column {c} must be EXACT under TPU dtypes")
    # float sums: storage is f32 so ingest already rounds values; compare
    # against the f32-rounded oracle with the compensated-sum tolerance
    want_f32 = big_df.assign(price=big_df.price.astype(np.float32)
                             .astype(np.float64)) \
        .groupby("g")["price"].sum().reset_index(drop=True)
    np.testing.assert_allclose(got["s_price"].to_numpy(),
                               want_f32.to_numpy(), rtol=1e-6)


def test_matmul_path_exact_ints(no_x64, big_store, big_df):
    eng = QueryEngine(big_store)
    _check_exact(eng.execute(_spec()), big_df)


def test_scatter_path_exact_ints(no_x64, big_store, big_df):
    cfg = Config({"sdot.engine.groupby.matmul.max.keys": 1})
    eng = QueryEngine(big_store, config=cfg)
    _check_exact(eng.execute(_spec()), big_df)


def test_sharded_exact_ints(no_x64, big_store, big_df):
    cfg = Config({"sdot.querycostmodel.enabled": False})
    eng = QueryEngine(big_store, mesh=make_mesh(), config=cfg)
    _check_exact(eng.execute(_spec()), big_df)
    assert eng.last_stats["sharded"] is True


def test_sharded_scatter_exact_ints(no_x64, big_store, big_df):
    cfg = Config({"sdot.engine.groupby.matmul.max.keys": 1,
                  "sdot.querycostmodel.enabled": False})
    eng = QueryEngine(big_store, mesh=make_mesh(), config=cfg)
    _check_exact(eng.execute(_spec()), big_df)


def test_global_aggregate_exact(no_x64, big_store, big_df):
    eng = QueryEngine(big_store)
    r = eng.execute(_spec(dimensions=()))
    got = r.to_pandas()
    assert int(got["s_big"][0]) == int(big_df["big"].sum())
    assert int(got["s_sgn"][0]) == int(big_df["sgn"].sum())
    assert int(got["n"][0]) == len(big_df)
    assert int(got["mn_big"][0]) == int(big_df["big"].min())
    assert int(got["mx_big"][0]) == int(big_df["big"].max())


def test_filtered_agg_exact(no_x64, big_store, big_df):
    from spark_druid_olap_tpu.ir.spec import SelectorFilter
    eng = QueryEngine(big_store)
    r = eng.execute(_spec(aggregations=(
        AggregationSpec("longsum", "s_big", field="big",
                        filter=SelectorFilter("g", "a")),
        AggregationSpec("count", "n"),
    ), dimensions=()))
    got = r.to_pandas()
    want = int(big_df.loc[big_df.g == "a", "big"].sum())
    assert int(got["s_big"][0]) == want


def test_open_interval_no_i32_overflow(no_x64, big_store, big_df):
    """An open-ended time interval carries a +-2^63-scale ms bound whose
    day number overflows i32 lanes unless interval_mask clamps it to the
    scan's day range (TPU SF1 q3 regression: 'l_shipdate > date X' =>
    interval (X, +inf))."""
    lo = int(np.datetime64("2019-03-01").astype("datetime64[ms]")
             .astype(np.int64))
    for hi in (2**62, 2**63 - 1):
        eng = QueryEngine(big_store)
        r = eng.execute(_spec(intervals=((lo, hi),)))
        got = r.to_pandas()
        sub = big_df[big_df.ts >= np.datetime64("2019-03-01")]
        want = _oracle(sub)
        got = got.sort_values("g").reset_index(drop=True)
        np.testing.assert_array_equal(
            got["s_big"].to_numpy().astype(np.int64),
            want.sort_values("g")["s_big"].to_numpy())
    # empty interval entirely above the data
    eng = QueryEngine(big_store)
    r = eng.execute(_spec(intervals=((2**62, 2**62 + 1),)))
    assert len(r.to_pandas()) == 0


def test_case_expression_sum_exact(no_x64, big_store, big_df):
    # sum(case when g='a' then big else 0 end): _expr_bounds must mark the
    # expression integer-exact so the lanes route fires
    eng = QueryEngine(big_store)
    case = E.Case(((E.Comparison("=", E.Column("g"), E.Literal("a")),
                    E.Column("big")),), E.Literal(0))
    r = eng.execute(_spec(aggregations=(
        AggregationSpec("longsum", "s", expr=case),
        AggregationSpec("count", "n"),
    ), dimensions=()))
    want = int(big_df.loc[big_df.g == "a", "big"].sum())
    assert int(r.to_pandas()["s"][0]) == want


def test_limb_kernel_unit(no_x64):
    """Direct kernel check: grouped int64 sums via 16-bit limbs vs numpy."""
    import jax.numpy as jnp
    from spark_druid_olap_tpu.ops import groupby as G
    r = np.random.default_rng(3)
    n, k = 200_000, 7
    v = r.integers(-(1 << 30), 1 << 30, n).astype(np.int32)
    key = r.integers(0, k, n).astype(np.int32)
    mask = r.random(n) < 0.8
    inputs = [G.AggInput("s", "sum", values=jnp.asarray(v).reshape(4, -1),
                         is_int=True, maxabs=float(1 << 30)),
              G.AggInput("__rows__", "count", is_int=True, maxabs=1.0)]
    routes = {"s": G.Route("s", "sum", "limbs"),
              "__rows__": G.Route("__rows__", "count", "limbs")}
    out = G._scatter_groupby(jnp.asarray(key).reshape(4, -1),
                             jnp.asarray(mask).reshape(4, -1),
                             k, inputs, routes)
    got = G.combine_route(routes["s"],
                          {k2: np.asarray(x) for k2, x in out.items()}, k)
    want = np.zeros(k, np.int64)
    np.add.at(want, key[mask], v[mask].astype(np.int64))
    np.testing.assert_array_equal(got, want)


# -----------------------------------------------------------------------------
# wide (beyond-int32) LONG columns
# -----------------------------------------------------------------------------

def test_wide_long_column_keeps_int64_storage():
    from spark_druid_olap_tpu.segment.column import (
        ColumnKind, build_metric_column)
    wide = build_metric_column(
        "w", np.array([1, 2**35, -5], dtype=np.int64), ColumnKind.LONG)
    assert wide.values.dtype == np.int64
    narrow = build_metric_column(
        "n", np.array([1, 2**30, -5], dtype=np.int64), ColumnKind.LONG)
    assert narrow.values.dtype == np.int32


def _wide_df():
    r = np.random.default_rng(5)
    n = 8_000
    return pd.DataFrame({
        "ts": (np.datetime64("2020-01-01")
               + r.integers(0, 100, n).astype("timedelta64[D]"))
        .astype("datetime64[ns]"),
        "g": r.choice(["a", "b", "c"], n),
        "w": r.integers(2**33, 2**45, n),     # values far beyond int32
    })


def test_wide_long_exact_on_x64_engine():
    # x64 backend carries wide values in native i64 routes: exact at any
    # magnitude (the module-scoped no_x64 fixture may be active; force on)
    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        _wide_long_exact_check()
    finally:
        jax.config.update("jax_enable_x64", prev)


def _wide_long_exact_check():
    df = _wide_df()
    st = SegmentStore()
    st.register(ingest_dataframe("wfact", df, time_column="ts",
                                 target_rows=2048))
    eng = QueryEngine(st)
    q = GroupByQuerySpec(
        datasource="wfact", dimensions=(DimensionSpec("g", "g"),),
        aggregations=(AggregationSpec("longsum", "s", field="w"),
                      AggregationSpec("longmin", "mn", field="w"),
                      AggregationSpec("longmax", "mx", field="w")))
    got = eng.execute(q).to_pandas().sort_values("g").reset_index(drop=True)
    want = df.groupby("g", as_index=False).agg(
        s=("w", "sum"), mn=("w", "min"), mx=("w", "max"))
    for c in ("s", "mn", "mx"):
        np.testing.assert_array_equal(
            got[c].to_numpy().astype(np.int64), want[c].to_numpy(),
            err_msg=f"{c} must be exact for wide longs")


def test_wide_long_falls_back_on_32bit_backend(no_x64):
    # a 32-bit backend cannot carry int64 without wrapping: the engine must
    # refuse (EngineFallback -> host tier), never return wrapped sums
    from spark_druid_olap_tpu.parallel.executor import EngineFallback
    df = _wide_df()
    st = SegmentStore()
    st.register(ingest_dataframe("wfact", df, time_column="ts",
                                 target_rows=2048))
    eng = QueryEngine(st)
    q = GroupByQuerySpec(
        datasource="wfact", dimensions=(DimensionSpec("g", "g"),),
        aggregations=(AggregationSpec("longsum", "s", field="w"),))
    with pytest.raises(EngineFallback):
        eng.execute(q)


def test_wide_long_sql_host_fallback_is_exact(no_x64, monkeypatch):
    # SDOT_FORCE_32BIT stops Context from re-enabling x64 on CPU, so this
    # exercises the exact TPU-dtype fallback wiring end-to-end
    monkeypatch.setenv("SDOT_FORCE_32BIT", "1")
    import spark_druid_olap_tpu as sdot
    df = _wide_df()
    ctx = sdot.Context()
    ctx.ingest_dataframe("wfact", df, time_column="ts", target_rows=2048)
    got = ctx.sql("select g, sum(w) as s from wfact group by g "
                  "order by g").to_pandas()
    assert ctx.history.entries()[-1].stats["mode"].startswith("host")
    want = df.groupby("g")["w"].sum().sort_index()
    np.testing.assert_array_equal(got["s"].to_numpy().astype(np.int64),
                                  want.to_numpy())


def test_wide_long_min_with_empty_groups_stays_exact():
    # filtered longmin leaving some groups empty must not round the
    # non-empty groups' wide values through f64
    prev = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    try:
        from spark_druid_olap_tpu.ir.spec import SelectorFilter
        df = pd.DataFrame({
            "ts": pd.to_datetime(["2020-01-01"] * 4),
            "g": ["a", "a", "b", "b"],
            "f": ["y", "y", "n", "n"],
            "w": np.array([2**60 + 1, 2**60 + 3, 2**61 + 7, 2**61 + 9],
                          dtype=np.int64),
        })
        st = SegmentStore()
        st.register(ingest_dataframe("wmin", df, time_column="ts"))
        q = GroupByQuerySpec(
            datasource="wmin", dimensions=(DimensionSpec("g", "g"),),
            aggregations=(AggregationSpec(
                "longmin", "mn", field="w",
                filter=SelectorFilter("f", "y")),))
        got = QueryEngine(st).execute(q).to_pandas() \
            .sort_values("g").reset_index(drop=True)
        assert got.loc[0, "mn"] == 2**60 + 1      # exact, not f64-rounded
        assert got.loc[1, "mn"] is None           # empty group -> null
    finally:
        jax.config.update("jax_enable_x64", prev)


def test_i32_scatter_sum_route_planned(no_x64):
    """Small-magnitude integer sums on the scatter path take the
    single-pass i32 scatter-add (maxabs * total_rows < 2^31) instead of
    the chunked limb scan; wide values keep limbs."""
    from spark_druid_olap_tpu.ops.groupby import AggInput, plan_routes
    metas = [AggInput("small", "sum", is_int=True, maxabs=100.0),
             AggInput("wide", "sum", is_int=True, maxabs=float(2 ** 30)),
             AggInput("n", "count", is_int=True, maxabs=1.0)]
    routes = plan_routes(metas, 1 << 20, matmul_max=4096,
                         n_rows=6_100_000)
    assert routes["small"].tag == "i32"
    assert routes["n"].tag == "i32"
    assert routes["wide"].tag == "limbs"
    # without a row bound the exact-by-construction limb path stays
    routes2 = plan_routes(metas, 1 << 20, matmul_max=4096)
    assert routes2["small"].tag == "limbs"
