"""TPU-dtype exactness tests.

These run with x64 DISABLED, which makes the CPU backend canonicalize to
i32/f32 — the same dtype environment as a real TPU (where f64 is unsupported
and i64 emulated). Every integer aggregate must then be EXACT via the lane /
limb / i32 routes (groupby.plan_route), not merely float-close: Druid's
aggregators are exact longs (reference ``DruidQuerySpec.scala:283-377``).

Covers the round-1 verdict's failure cases: int columns with values > 2^24
(min/max/anyvalue would round in f32), sums > 2^32 (overflow i32, round in
f32), on both the MXU one-hot-matmul path and the scatter path, single-chip
and sharded over the virtual 8-device mesh (limb psum + per-chip ff host
combine).
"""

import numpy as np
import pandas as pd
import pytest
import jax

from spark_druid_olap_tpu.segment.ingest import ingest_dataframe
from spark_druid_olap_tpu.segment.store import SegmentStore
from spark_druid_olap_tpu.parallel.executor import QueryEngine
from spark_druid_olap_tpu.parallel.mesh import make_mesh
from spark_druid_olap_tpu.utils.config import Config
from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ir.spec import (
    AggregationSpec,
    DimensionSpec,
    GroupByQuerySpec,
)

N_ROWS = 60_000


@pytest.fixture(scope="module")
def no_x64():
    """TPU dtype environment: i32/f32 canonical types."""
    jax.config.update("jax_enable_x64", False)
    yield
    jax.config.update("jax_enable_x64", True)


@pytest.fixture(scope="module")
def big_df():
    r = np.random.default_rng(11)
    ts = (np.datetime64("2019-01-01")
          + r.integers(0, 365, N_ROWS).astype("timedelta64[D]"))
    return pd.DataFrame({
        "ts": ts.astype("datetime64[ns]"),
        "g": r.choice(["a", "b", "c", "d"], N_ROWS),
        # values straddling 2^24 (f32 integer-exactness cliff) and
        # well past 2^20 so the 60k-row sums exceed 2^32
        "big": (r.integers(0, 1 << 30, N_ROWS)
                + (1 << 24)).astype(np.int64),
        "sgn": r.integers(-(1 << 26), 1 << 26, N_ROWS).astype(np.int64),
        "small": r.integers(0, 100, N_ROWS).astype(np.int64),
        "price": np.round(r.uniform(1e3, 1e5, N_ROWS), 2),
    })


@pytest.fixture(scope="module")
def big_store(big_df):
    ds = ingest_dataframe("big", big_df, time_column="ts",
                          target_rows=8192)
    st = SegmentStore()
    st.register(ds)
    return st


def _spec(**kw):
    base = dict(
        datasource="big",
        dimensions=(DimensionSpec("g", "g"),),
        aggregations=(
            AggregationSpec("longsum", "s_big", field="big"),
            AggregationSpec("longsum", "s_sgn", field="sgn"),
            AggregationSpec("longsum", "s_small", field="small"),
            AggregationSpec("longmin", "mn_big", field="big"),
            AggregationSpec("longmax", "mx_big", field="big"),
            AggregationSpec("longmin", "mn_sgn", field="sgn"),
            AggregationSpec("count", "n"),
            AggregationSpec("doublesum", "s_price", field="price"),
        ))
    base.update(kw)
    return GroupByQuerySpec(**base)


def _oracle(df):
    g = df.groupby("g")
    return pd.DataFrame({
        "s_big": g["big"].sum(),
        "s_sgn": g["sgn"].sum(),
        "s_small": g["small"].sum(),
        "mn_big": g["big"].min(),
        "mx_big": g["big"].max(),
        "mn_sgn": g["sgn"].min(),
        "n": g.size(),
        "s_price": g["price"].sum(),
    }).reset_index()


def _check_exact(r, big_df):
    got = r.to_pandas().sort_values("g").reset_index(drop=True)
    want = _oracle(big_df).sort_values("g").reset_index(drop=True)
    for c in ("s_big", "s_sgn", "s_small", "mn_big", "mx_big", "mn_sgn",
              "n"):
        np.testing.assert_array_equal(
            got[c].to_numpy().astype(np.int64), want[c].to_numpy(),
            err_msg=f"column {c} must be EXACT under TPU dtypes")
    # float sums: storage is f32 so ingest already rounds values; compare
    # against the f32-rounded oracle with the compensated-sum tolerance
    want_f32 = big_df.assign(price=big_df.price.astype(np.float32)
                             .astype(np.float64)) \
        .groupby("g")["price"].sum().reset_index(drop=True)
    np.testing.assert_allclose(got["s_price"].to_numpy(),
                               want_f32.to_numpy(), rtol=1e-6)


def test_matmul_path_exact_ints(no_x64, big_store, big_df):
    eng = QueryEngine(big_store)
    _check_exact(eng.execute(_spec()), big_df)


def test_scatter_path_exact_ints(no_x64, big_store, big_df):
    cfg = Config({"sdot.engine.groupby.matmul.max.keys": 1})
    eng = QueryEngine(big_store, config=cfg)
    _check_exact(eng.execute(_spec()), big_df)


def test_sharded_exact_ints(no_x64, big_store, big_df):
    cfg = Config({"sdot.querycostmodel.enabled": False})
    eng = QueryEngine(big_store, mesh=make_mesh(), config=cfg)
    _check_exact(eng.execute(_spec()), big_df)
    assert eng.last_stats["sharded"] is True


def test_sharded_scatter_exact_ints(no_x64, big_store, big_df):
    cfg = Config({"sdot.engine.groupby.matmul.max.keys": 1,
                  "sdot.querycostmodel.enabled": False})
    eng = QueryEngine(big_store, mesh=make_mesh(), config=cfg)
    _check_exact(eng.execute(_spec()), big_df)


def test_global_aggregate_exact(no_x64, big_store, big_df):
    eng = QueryEngine(big_store)
    r = eng.execute(_spec(dimensions=()))
    got = r.to_pandas()
    assert int(got["s_big"][0]) == int(big_df["big"].sum())
    assert int(got["s_sgn"][0]) == int(big_df["sgn"].sum())
    assert int(got["n"][0]) == len(big_df)
    assert int(got["mn_big"][0]) == int(big_df["big"].min())
    assert int(got["mx_big"][0]) == int(big_df["big"].max())


def test_filtered_agg_exact(no_x64, big_store, big_df):
    from spark_druid_olap_tpu.ir.spec import SelectorFilter
    eng = QueryEngine(big_store)
    r = eng.execute(_spec(aggregations=(
        AggregationSpec("longsum", "s_big", field="big",
                        filter=SelectorFilter("g", "a")),
        AggregationSpec("count", "n"),
    ), dimensions=()))
    got = r.to_pandas()
    want = int(big_df.loc[big_df.g == "a", "big"].sum())
    assert int(got["s_big"][0]) == want


def test_case_expression_sum_exact(no_x64, big_store, big_df):
    # sum(case when g='a' then big else 0 end): _expr_bounds must mark the
    # expression integer-exact so the lanes route fires
    eng = QueryEngine(big_store)
    case = E.Case(((E.Comparison("=", E.Column("g"), E.Literal("a")),
                    E.Column("big")),), E.Literal(0))
    r = eng.execute(_spec(aggregations=(
        AggregationSpec("longsum", "s", expr=case),
        AggregationSpec("count", "n"),
    ), dimensions=()))
    want = int(big_df.loc[big_df.g == "a", "big"].sum())
    assert int(r.to_pandas()["s"][0]) == want


def test_limb_kernel_unit(no_x64):
    """Direct kernel check: grouped int64 sums via 16-bit limbs vs numpy."""
    import jax.numpy as jnp
    from spark_druid_olap_tpu.ops import groupby as G
    r = np.random.default_rng(3)
    n, k = 200_000, 7
    v = r.integers(-(1 << 30), 1 << 30, n).astype(np.int32)
    key = r.integers(0, k, n).astype(np.int32)
    mask = r.random(n) < 0.8
    inputs = [G.AggInput("s", "sum", values=jnp.asarray(v).reshape(4, -1),
                         is_int=True, maxabs=float(1 << 30)),
              G.AggInput("__rows__", "count", is_int=True, maxabs=1.0)]
    routes = {"s": G.Route("s", "sum", "limbs"),
              "__rows__": G.Route("__rows__", "count", "limbs")}
    out = G._scatter_groupby(jnp.asarray(key).reshape(4, -1),
                             jnp.asarray(mask).reshape(4, -1),
                             k, inputs, routes)
    got = G.combine_route(routes["s"],
                          {k2: np.asarray(x) for k2, x in out.items()}, k)
    want = np.zeros(k, np.int64)
    np.add.at(want, key[mask], v[mask].astype(np.int64))
    np.testing.assert_array_equal(got, want)
