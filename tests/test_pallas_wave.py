"""Pallas wave mega-kernel (ops/pallas_wave.py + sharedscan wave path).

Interpreter-mode CI differentials: with ``SDOT_PALLAS=interpret`` (set
per-batch via ``_interpret_env`` — see its docstring for why it is NOT
an autouse fixture) the hand-scheduled wave kernel runs through
``pl.pallas_call(..., interpret=True)`` on CPU, so every test here
guards the kernel's semantics chip-independently:

- coalesced storm answers under the wave kernel == sequential solo
  answers AND == the jaxpr-fused program's answers (kill-switch A/B) —
  integer aggregates, counts, and sketch registers exactly (Neumaier
  int sums and min-algebra are order-free), float sums within the
  standard frame tolerance;
- the kill switch (``sdot.pallas.wave.enabled=false``) routes back to
  the jaxpr program with zero launches;
- a lane the kernel cannot lower (pattern filter -> dictionary-LUT
  gather, rejected by the trace probe) falls back to the jaxpr program
  WITHOUT changing routing tiers: the group still coalesces, nothing
  bounces solo;
- launch accounting: one kernel launch per dispatch wave on the canned
  4-lane storm, surfaced through coalescer stats and per-constituent
  stats.
"""

import contextlib
import os

import numpy as np
import pytest

import spark_druid_olap_tpu as sdot
from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.tools import tpch

from conftest import assert_frames_equal
from test_sharedscan import (
    AGGS,
    WINDOW_MS,
    _engine,
    _ref_engine,
    _run_concurrent,
    _sales_batch,
    _storm_batch,
)


@contextlib.contextmanager
def _interpret_env():
    """Make the wave kernel available via ``pl.pallas_call(...,
    interpret=True)`` — the chip-independent CI configuration.

    Scoped to the wave-engine batch runs ONLY, deliberately: with
    ``SDOT_PALLAS=interpret`` set process-wide, every solo reference and
    jaxpr-fused comparison would also route its ``'ffl'`` sum/count
    lanes through interpreter-mode ``pallas_groupby`` (~20x slower than
    the XLA route for identical answers — measured 28s vs 1.4s for one
    solo reference sweep). Keeping references on the pure-XLA path both
    fits the tier-1 budget and makes the differential stronger: the
    interpreted wave kernel is compared against the canonical XLA
    lowering, not against another interpreter artifact."""
    old = os.environ.get("SDOT_PALLAS")
    os.environ["SDOT_PALLAS"] = "interpret"
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("SDOT_PALLAS", None)
        else:
            os.environ["SDOT_PALLAS"] = old


def _wave_engine(store, **overrides):
    cfg = {"sdot.pallas.wave.enabled": True}
    cfg.update(overrides)
    return _engine(store, **cfg)


def _jaxpr_engine(store, **overrides):
    cfg = {"sdot.pallas.wave.enabled": False}
    cfg.update(overrides)
    return _engine(store, **cfg)


def _pallas_delta(eng, fn):
    p0 = eng.sharedscan.stats()["pallas"]
    out = fn()
    p1 = eng.sharedscan.stats()["pallas"]
    return out, {k: p1[k] - p0[k] for k in p1 if k != "vmem_bytes_peak"}


def _run_batch(eng, specs):
    res, errs, stats = _run_concurrent(eng, specs, collect_stats=True)
    assert not any(errs), [e for e in errs if e]
    return res, stats


def _assert_matches(got_frames, want_frames, exact_cols=()):
    for got, want in zip(got_frames, want_frames):
        assert_frames_equal(got, want)
        for c in exact_cols:
            if c in got.columns:
                assert np.array_equal(got[c].to_numpy(),
                                      want[c].to_numpy()), c


# -- differentials ------------------------------------------------------------

def test_wave_sales_mixed_matches_sequential_and_jaxpr(store):
    """The standard mixed batch (GroupBy / filtered GroupBy / monthly
    Timeseries / interval Timeseries / TopN) under the wave kernel must
    match both the solo sequential reference and the jaxpr-fused program,
    with integer aggregates exact."""
    specs = _sales_batch()
    ref = [_ref_engine(store).execute(q).to_pandas() for q in specs]
    eng = _wave_engine(store)
    with _interpret_env():
        (res, _), dp = _pallas_delta(eng, lambda: _run_batch(eng, specs))
    assert dp["launches"] >= 1, dp
    assert dp["fallbacks"] == 0, dp
    _assert_matches(res, ref, exact_cols=("units", "n"))
    jx, _ = _run_batch(_jaxpr_engine(store), specs)
    _assert_matches(res, jx, exact_cols=("units", "n"))


def test_wave_integer_storm_bit_exact(store):
    """All-integer canned storm with a COMMUTED shared predicate (a AND b
    vs b AND a — canonicalized to one CSE node): wave answers must be
    bitwise identical to both solo and jaxpr paths (Neumaier integer
    sums, counts, and int min/max are exact in the f32 scratch)."""
    iaggs = (S.AggregationSpec("longsum", "units", field="qty"),
             S.AggregationSpec("longmin", "qmin", field="qty"),
             S.AggregationSpec("longmax", "qmax", field="qty"),
             S.AggregationSpec("count", "n"))
    a = S.SelectorFilter("status", "O")
    b = S.SelectorFilter("flag", "A")
    specs = [
        S.GroupByQuerySpec("sales", (S.DimensionSpec("region", "region"),),
                           iaggs, filter=S.LogicalFilter("and", (a, b))),
        S.GroupByQuerySpec("sales", (S.DimensionSpec("flag", "flag"),),
                           iaggs, filter=S.LogicalFilter("and", (b, a))),
        S.TimeseriesQuerySpec("sales", iaggs,
                              granularity=S.Granularity("year")),
        S.GroupByQuerySpec("sales", (S.DimensionSpec("status", "status"),),
                           iaggs),
    ]
    ref = [_ref_engine(store).execute(q).to_pandas() for q in specs]
    eng = _wave_engine(store)
    with _interpret_env():
        (res, _), dp = _pallas_delta(eng, lambda: _run_batch(eng, specs))
    assert dp["launches"] >= 1 and dp["fallbacks"] == 0, dp
    exact = ("units", "qmin", "qmax", "n")
    _assert_matches(res, ref, exact_cols=exact)
    jx, _ = _run_batch(_jaxpr_engine(store), specs)
    _assert_matches(res, jx, exact_cols=exact)


def test_wave_sketch_lanes_match(store):
    """HLL (XLA epilogue inside the same jit) and theta (in-kernel
    register minima) lanes: estimates must be exactly equal to the solo
    path — both registers are bit-exact by construction (HLL reuses the
    identical XLA ops; theta is order-free min algebra on the identical
    hash stream)."""
    saggs = (S.AggregationSpec("cardinality", "uprod", field="product"),
             S.AggregationSpec("thetasketch", "tprod", field="product"),
             S.AggregationSpec("longsum", "units", field="qty"),
             S.AggregationSpec("count", "n"))
    specs = [
        S.GroupByQuerySpec("sales", (S.DimensionSpec("region", "region"),),
                           saggs),
        S.GroupByQuerySpec("sales", (S.DimensionSpec("flag", "flag"),),
                           saggs, filter=S.SelectorFilter("status", "O")),
        S.TimeseriesQuerySpec("sales", saggs,
                              granularity=S.Granularity("year")),
    ]
    ref = [_ref_engine(store).execute(q).to_pandas() for q in specs]
    eng = _wave_engine(store)
    with _interpret_env():
        (res, _), dp = _pallas_delta(eng, lambda: _run_batch(eng, specs))
    assert dp["launches"] >= 1 and dp["fallbacks"] == 0, dp
    _assert_matches(res, ref, exact_cols=("uprod", "tprod", "units", "n"))


def test_wave_tpch_storm(tpch_wave_ctx):
    """TPC-H star storm (shared return-flag predicate across lanes +
    a sketch lane) through the session context: wave answers match the
    solo reference and the leader's statement stats surface the launch."""
    aggs = (S.AggregationSpec("doublesum", "revenue",
                              field="l_extendedprice"),
            S.AggregationSpec("longsum", "qty", field="l_quantity"),
            S.AggregationSpec("cardinality", "uparts", field="p_brand"),
            S.AggregationSpec("count", "n"))
    shared = S.SelectorFilter("l_returnflag", "R")
    specs = [
        S.GroupByQuerySpec("tpch_flat",
                           (S.DimensionSpec("l_linestatus", "l_linestatus"),),
                           aggs, filter=shared),
        S.GroupByQuerySpec("tpch_flat",
                           (S.DimensionSpec("c_mktsegment", "seg"),),
                           aggs, filter=shared),
        S.TimeseriesQuerySpec("tpch_flat", aggs,
                              granularity=S.Granularity("year")),
    ]
    eng = tpch_wave_ctx.engine
    ref = [_ref_engine(eng.store).execute(q).to_pandas() for q in specs]
    with _interpret_env():
        (res, _), dp = _pallas_delta(eng, lambda: _run_batch(eng, specs))
    assert dp["launches"] >= 1 and dp["fallbacks"] == 0, dp
    _assert_matches(res, ref, exact_cols=("qty", "uparts", "n"))


@pytest.fixture(scope="module")
def tpch_wave_ctx():
    ctx = sdot.Context({"sdot.sharedscan.enabled": True,
                        "sdot.wlm.batch.window.ms": WINDOW_MS,
                        "sdot.pallas.wave.enabled": True})
    tpch.setup_context(ctx, sf=0.002, target_rows=4096, flat_only=True)
    return ctx


# -- kill switch + fallback ---------------------------------------------------

def _small_storm():
    """3-lane batch for the routing-gate tests: the gates fire before any
    kernel work, so these lanes stay deliberately cheap (the env-set
    batches still pay interpreter-mode 'ffl' lanes on the jaxpr program
    they route to)."""
    shared = S.SelectorFilter("status", "O")
    return [
        S.GroupByQuerySpec("sales", (S.DimensionSpec("region", "region"),),
                           AGGS, filter=shared),
        S.GroupByQuerySpec("sales", (S.DimensionSpec("flag", "flag"),),
                           AGGS, filter=shared),
        S.TimeseriesQuerySpec("sales", AGGS,
                              granularity=S.Granularity("year")),
    ]


def test_wave_kill_switch_routes_to_jaxpr(store):
    """``sdot.pallas.wave.enabled=false`` must take the jaxpr program
    (zero kernel launches, no fallback ticks — the wave path was never
    attempted) with identical answers, even while the wave path IS
    available (interpret env set for the batch)."""
    specs = _small_storm()
    ref = [_ref_engine(store).execute(q).to_pandas() for q in specs]
    eng = _jaxpr_engine(store)
    with _interpret_env():
        (res, _), dp = _pallas_delta(eng, lambda: _run_batch(eng, specs))
    assert dp == {"launches": 0, "tiles": 0, "fallbacks": 0}, dp
    _assert_matches(res, ref, exact_cols=("units", "n"))


def test_wave_fallback_keeps_group_fused(store):
    """A lane whose filter lowers through a dictionary LUT (a regex
    selecting 25 alternating dictionary codes exceeds the fused
    range-chain cap in BOTH polarities, so ``_take_mask`` falls to a
    real gather — outside the Mosaic-safe whitelist) must reject at
    the trace probe and lower the WHOLE group through the jaxpr-fused
    program: pallas_fallbacks ticks, zero launches, the group still
    coalesces (routing tiers unchanged — nothing bounces solo), and
    answers still match."""
    specs = [
        S.GroupByQuerySpec("sales", (S.DimensionSpec("region", "region"),),
                           AGGS,
                           filter=S.PatternFilter("product", "regex",
                                                  "[13579]$")),
        S.GroupByQuerySpec("sales", (S.DimensionSpec("flag", "flag"),),
                           AGGS),
        S.TimeseriesQuerySpec("sales", AGGS,
                              granularity=S.Granularity("year")),
    ]
    ref = [_ref_engine(store).execute(q).to_pandas() for q in specs]
    eng = _wave_engine(store)
    c0 = eng.sharedscan.stats()
    with _interpret_env():
        (res, _), dp = _pallas_delta(eng, lambda: _run_batch(eng, specs))
    c1 = eng.sharedscan.stats()
    assert dp["launches"] == 0, dp
    assert dp["fallbacks"] == 1, dp
    assert c1["groups_coalesced"] - c0["groups_coalesced"] == 1, c1
    assert c1["fallbacks"] - c0["fallbacks"] == 0, c1
    _assert_matches(res, ref, exact_cols=("units", "n"))


def test_wave_max_lanes_gate(store):
    """Groups wider than ``sdot.pallas.wave.max.lanes`` take the jaxpr
    program via the static precheck (no fallback tick — never attempted)
    and still coalesce."""
    specs = _small_storm()
    ref = [_ref_engine(store).execute(q).to_pandas() for q in specs]
    eng = _wave_engine(store, **{"sdot.pallas.wave.max.lanes": 1})
    c0 = eng.sharedscan.stats()
    with _interpret_env():
        (res, _), dp = _pallas_delta(eng, lambda: _run_batch(eng, specs))
    c1 = eng.sharedscan.stats()
    assert dp == {"launches": 0, "tiles": 0, "fallbacks": 0}, dp
    assert c1["groups_coalesced"] - c0["groups_coalesced"] == 1, c1
    _assert_matches(res, ref, exact_cols=("units", "n"))


# -- launch accounting --------------------------------------------------------

def test_wave_one_launch_per_wave_canned_storm(store):
    """CI launch-accounting smoke: the canned 4-lane storm runs as ONE
    kernel launch per dispatch wave — coalescer counters and every
    constituent's own stats agree."""
    specs = _storm_batch()
    eng = _wave_engine(store)
    with _interpret_env():
        ((res, stats), dp) = _pallas_delta(eng,
                                           lambda: _run_batch(eng, specs))
    waves = {s["waves"] for s in stats if s.get("sharedscan")}
    assert waves, "no constituent reported sharedscan stats"
    n_waves = max(waves)
    assert dp["launches"] == n_waves, (dp, n_waves)
    assert dp["tiles"] >= dp["launches"], dp
    per_member = [s["sharedscan"]["pallas"] for s in stats
                  if s.get("sharedscan")]
    for pm in per_member:
        assert pm is not None, "wave group member missing pallas stats"
        assert pm["launches"] == n_waves, pm
        assert pm["block_rows"] >= 128, pm
        assert pm["vmem_bytes"] > 0, pm


def test_wave_compile_cache_key_isolation(store):
    """Flipping the kill switch on one engine must re-key the fused
    program (wave and jaxpr programs never collide in the compile
    cache) and keep answers identical across the flip."""
    specs = _small_storm()
    eng = _wave_engine(store)
    with _interpret_env():
        res1, _ = _run_batch(eng, specs)
        n1 = sum(1 for sig in eng._programs if sig and sig[0] == "aggmulti")
        eng.config.set("sdot.pallas.wave.enabled", False)
        res2, _ = _run_batch(eng, specs)
        n2 = sum(1 for sig in eng._programs if sig and sig[0] == "aggmulti")
    assert n2 == n1 + 1, (n1, n2)
    _assert_matches(res1, res2, exact_cols=("units", "n"))
