"""Device HAVING compaction (reference: Druid evaluates HavingSpec on the
data node — ``DruidQuerySpec`` having tree — instead of shipping every
group to the broker; here the exact mask + count travel first, then only
passing groups).

Exactness: limb sums compare lexicographically at any magnitude
(ops.groupby.limbs_compare); the host epilogue re-applies HAVING over the
exact finals, so the device mask is a transfer filter, never the source
of truth.
"""

import numpy as np
import pandas as pd
import pytest

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ir.spec import (
    AggregationSpec, DimensionSpec, GroupByQuerySpec, HavingSpec,
)
from spark_druid_olap_tpu.ops import groupby as G
from spark_druid_olap_tpu.parallel.executor import QueryEngine
from spark_druid_olap_tpu.parallel.mesh import make_mesh
from spark_druid_olap_tpu.segment.ingest import ingest_dataframe
from spark_druid_olap_tpu.segment.store import SegmentStore
from spark_druid_olap_tpu.utils.config import Config

N = 80_000
N_CUST = 70_000          # above having.device.min.keys (2^16)


def _df():
    rng = np.random.default_rng(41)
    return pd.DataFrame({
        "ts": (np.datetime64("2022-01-01")
               + rng.integers(0, 365, N).astype("timedelta64[D]"))
        .astype("datetime64[ns]"),
        "cust": rng.choice([f"c{i:05d}" for i in range(N_CUST)], N),
        "qty": rng.integers(1, 100, N).astype(np.int64),
        # wide values: per-group sums can pass 2^31, testing the
        # lexicographic limb comparison beyond the i32 range
        "big": rng.integers(2**28, 2**31, N).astype(np.int64),
        "price": np.round(rng.uniform(1, 500, N), 2),
    })


@pytest.fixture(scope="module")
def hdf():
    return _df()


@pytest.fixture(scope="module")
def hstore(hdf):
    st = SegmentStore()
    st.register(ingest_dataframe("fact", hdf, time_column="ts",
                                 target_rows=1 << 14))
    return st


AGGS = (
    AggregationSpec("longsum", "s_qty", field="qty"),
    AggregationSpec("doublesum", "s_price", field="price"),
    AggregationSpec("count", "n"),
)


def _q(metric, op, lit, aggs=AGGS):
    return GroupByQuerySpec(
        datasource="fact",
        dimensions=(DimensionSpec("cust", "cust"),),
        aggregations=aggs,
        having=HavingSpec(E.Comparison(op, E.Column(metric),
                                       E.Literal(lit))))


def _want(df, pred):
    g = df.groupby("cust", as_index=False).agg(
        s_qty=("qty", "sum"), s_price=("price", "sum"), n=("qty", "size"))
    return g[pred(g)]


def _check(eng, got, want):
    got = got.sort_values("cust").reset_index(drop=True)
    want = want.sort_values("cust").reset_index(drop=True)
    assert len(got) == len(want)
    np.testing.assert_array_equal(got["cust"].to_numpy().astype(str),
                                  want["cust"].to_numpy())
    np.testing.assert_array_equal(got["s_qty"].to_numpy().astype(np.int64),
                                  want["s_qty"].to_numpy())
    np.testing.assert_array_equal(got["n"].to_numpy().astype(np.int64),
                                  want["n"].to_numpy())


@pytest.mark.parametrize("op,pred", [
    (">", lambda g: g.s_qty > 200),
    (">=", lambda g: g.s_qty >= 200),
    ("<", lambda g: g.s_qty < 40),
    ("=", lambda g: g.s_qty == 100),
])
def test_having_device_ops(hstore, hdf, op, pred):
    lit = {"<": 40, "=": 100}.get(op, 200)
    eng = QueryEngine(hstore, config=Config(
        {"sdot.engine.having.device.min.keys": 1024}))
    got = eng.execute(_q("s_qty", op, lit)).to_pandas()
    assert eng.last_stats["having_device"] > 0
    _check(eng, got, _want(hdf, pred))


def test_having_device_matches_host_path(hstore):
    q = _q("n", ">", 2)
    dev = QueryEngine(hstore, config=Config(
        {"sdot.engine.having.device.min.keys": 1024}))
    got = dev.execute(q).to_pandas()
    assert dev.last_stats["having_device"] > 0
    host = QueryEngine(hstore, config=Config(
        {"sdot.engine.having.device.min.keys": 1 << 30}))
    want = host.execute(q).to_pandas()
    assert host.last_stats["having_device"] == 0
    pd.testing.assert_frame_equal(
        got.sort_values("cust").reset_index(drop=True),
        want.sort_values("cust").reset_index(drop=True))


def test_having_device_sharded(hstore, hdf):
    eng = QueryEngine(hstore, mesh=make_mesh(), config=Config(
        {"sdot.querycostmodel.enabled": False,
         "sdot.engine.having.device.min.keys": 1024}))
    got = eng.execute(_q("s_qty", ">", 200)).to_pandas()
    assert eng.last_stats["sharded"] is True
    assert eng.last_stats["having_device"] > 0
    _check(eng, got, _want(hdf, lambda g: g.s_qty > 200))


def test_having_device_wide_sums(hstore, hdf):
    """Per-group sums beyond 2^31: the limb comparison must stay exact."""
    lit = int(hdf.groupby("cust")["big"].sum().median())
    aggs = (AggregationSpec("longsum", "s_big", field="big"),
            AggregationSpec("count", "n"))
    eng = QueryEngine(hstore, config=Config(
        {"sdot.engine.having.device.min.keys": 1024}))
    got = eng.execute(_q("s_big", ">", lit, aggs=aggs)).to_pandas()
    assert eng.last_stats["having_device"] > 0
    g = hdf.groupby("cust", as_index=False).agg(s_big=("big", "sum"))
    want = g[g.s_big > lit]
    assert len(got) == len(want)
    np.testing.assert_array_equal(
        np.sort(got["s_big"].to_numpy().astype(np.int64)),
        np.sort(want["s_big"].to_numpy()))


def test_having_float_metric_stays_host_on_tpu_dtypes(hstore):
    """Under TPU dtypes (x64 off) float sums ride the f32 ff route —
    borderline groups could flip, so the compactor must NOT engage there.
    (On x64 the f64 route is exact and engaging is correct.)"""
    import jax
    jax.config.update("jax_enable_x64", False)
    try:
        eng = QueryEngine(hstore, config=Config(
            {"sdot.engine.having.device.min.keys": 1024}))
        got = eng.execute(_q("s_price", ">", 1000)).to_pandas()
        assert eng.last_stats["having_device"] == 0
        assert len(got) > 0
    finally:
        jax.config.update("jax_enable_x64", True)


def test_limbs_compare_unit():
    vals = np.array([-2**40, -5, 0, 3, 2**20, 2**35, 2**45], dtype=np.int64)
    import jax.numpy as jnp
    limbs = np.stack([(vals & 0xFFFF), (vals >> 16) & 0xFFFF,
                      (vals >> 32) & 0xFFFF, vals >> 48],
                     axis=1).astype(np.int32)
    for lit in (-2**40, -6, -5, 0, 3, 2**20 + 1, 2**35, 2**44):
        for op, fn in ((">", np.greater), (">=", np.greater_equal),
                       ("<", np.less), ("<=", np.less_equal),
                       ("=", np.equal), ("!=", np.not_equal)):
            got = np.asarray(G.limbs_compare(jnp.asarray(limbs), lit, op))
            np.testing.assert_array_equal(
                got, fn(vals, lit), err_msg=f"{op} {lit}")
