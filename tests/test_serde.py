"""Query-spec JSON serialization round-trips (≈ reference SerTest — json4s
round-trips of every QuerySpec variant, SerTest.scala 184 LoC)."""

import pytest

from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.ir.serde import (
    query_from_json,
    query_to_json,
)


def rt(q):
    q2 = query_from_json(query_to_json(q))
    assert q2 == q, f"\n{q2}\n!=\n{q}"
    return q2


FILTER = S.LogicalFilter("and", (
    S.SelectorFilter("region", "east"),
    S.BoundFilter("qty", lower=5, upper=40, upper_strict=True, numeric=True),
    S.InFilter("flag", ("A", "N")),
    S.PatternFilter("product", "like", "p0%"),
    S.LogicalFilter("not", (S.NullFilter("status"),)),
    S.SpatialFilter("pickup", ("lat", "lon"), (1.0, 2.0), (3.0, 4.0)),
    S.ExprFilter(E.Comparison(">", E.BinaryOp(
        "*", E.Column("price"), E.Literal(2)), E.Literal(10))),
))

AGGS = (
    S.AggregationSpec("count", "c"),
    S.AggregationSpec("doublesum", "s", field="price"),
    S.AggregationSpec("longmin", "mn", field="qty"),
    S.AggregationSpec("doublemax", "mx", field="price",
                      filter=S.SelectorFilter("flag", "A")),
    S.AggregationSpec("cardinality", "np", field="product"),
    S.AggregationSpec("doublesum", "expr_s", expr=E.BinaryOp(
        "*", E.Column("price"), E.BinaryOp("-", E.Literal(1),
                                           E.Column("discount")))),
)

POSTS = (S.PostAggregationSpec("ratio", E.BinaryOp(
    "/", E.Column("s"), E.Column("c"))),)


def test_groupby_roundtrip():
    rt(S.GroupByQuerySpec(
        datasource="sales",
        dimensions=(S.DimensionSpec("region", "region"),
                    S.DimensionSpec("ts", "month",
                                    S.TimeExtraction("month")),
                    S.DimensionSpec("product", "pid",
                                    S.RegexExtraction("p(\\d+)", 1, True)),
                    S.DimensionSpec("region", "zone", S.LookupExtraction(
                        (("east", "atlantic"), ("west", None)),
                        retain_missing=True))),
        aggregations=AGGS, post_aggregations=POSTS, filter=FILTER,
        having=S.HavingSpec(E.Comparison(">", E.Column("s"),
                                         E.Literal(100))),
        limit=S.LimitSpec((S.OrderByColumn("s", ascending=False),), 10),
        granularity=S.Granularity("month"),
        intervals=((1000, 2000), (3000, 4000)),
        context=S.QueryContext(query_id="q-1", timeout_millis=5000)))


def test_timeseries_roundtrip():
    rt(S.TimeseriesQuerySpec(
        datasource="sales", aggregations=AGGS[:2],
        post_aggregations=POSTS,
        granularity=S.Granularity("duration", duration_millis=3600_000),
        filter=S.SelectorFilter("flag", None),
        intervals=((0, 10_000),)))


def test_topn_roundtrip():
    rt(S.TopNQuerySpec(
        datasource="sales", dimension=S.DimensionSpec("product", "product"),
        metric="s", threshold=25, aggregations=AGGS[:3],
        filter=S.BoundFilter("region", lower="a", upper="m")))


def test_select_roundtrip():
    rt(S.SelectQuerySpec(
        datasource="sales", columns=("ts", "region", "price"),
        filter=S.InFilter("region", ("east",)),
        intervals=((5, 50),), page_size=500, page_offset=1500,
        descending=True))


def test_search_roundtrip():
    rt(S.SearchQuerySpec(
        datasource="sales", dimensions=("region", "product"),
        query="ast", case_sensitive=True, limit=7))


def test_default_datasource_applies():
    q = query_from_json('{"queryType": "timeseries", "aggregations": '
                        '[{"type": "count", "name": "c"}]}',
                        default_ds="sales")
    assert q.datasource == "sales"


def test_unknown_query_type_raises():
    with pytest.raises(ValueError):
        query_from_json('{"queryType": "mystery"}')


def test_expr_sql_stability():
    # expression serde preserves evaluation structure
    e = E.Case(((E.Comparison("=", E.Column("a"), E.Literal("x")),
                 E.Literal(1)),), E.Literal(0))
    q = S.GroupByQuerySpec(
        datasource="t", dimensions=(S.DimensionSpec("a", "a"),),
        aggregations=(S.AggregationSpec("doublesum", "s", expr=e),))
    rt(q)


def test_keyed_lookup_roundtrip():
    # broadcast-join lookup tables survive the wire (NaN-coded NULLs
    # travel as JSON null)
    import numpy as np
    tab = E.FrozenKeyedTable(np.array([5, 2, 9]),
                             np.array([1.5, np.nan, -3.0]))
    e = E.Comparison("<", E.Column("qty"),
                     E.KeyedLookup(E.Column("k"), tab, 0.0))
    q = S.GroupByQuerySpec(
        datasource="t", dimensions=(S.DimensionSpec("a", "a"),),
        aggregations=(S.AggregationSpec("count", "n"),),
        filter=S.ExprFilter(e))
    rt(q)


def test_keyed_lookup2_roundtrip():
    import numpy as np
    tab = E.FrozenKeyedTable2(np.array([5, 2, 2]), np.array([1, 9, 3]),
                              np.array([1.5, np.nan, -3.0]))
    e = E.Comparison(">", E.Column("qty"),
                     E.KeyedLookup2(E.Column("a"), E.Column("b"), tab))
    q = S.GroupByQuerySpec(
        datasource="t", dimensions=(S.DimensionSpec("a", "a"),),
        aggregations=(S.AggregationSpec("count", "n"),),
        filter=S.ExprFilter(e))
    rt(q)
