"""Streaming append (segment/append.py) edge cases.

Differential backbone: a datasource built by N appends must answer
queries identically to one batch-ingested from the concatenated frame
(segmentation differs; results must not). Plus the ISSUE-listed edges:
empty Arrow batch, all-null column, and an append racing a checkpoint.
"""

import threading

import numpy as np
import pandas as pd
import pytest

import spark_druid_olap_tpu as sdot
from spark_druid_olap_tpu.segment.append import append_dataframe

from conftest import assert_frames_equal


def _batch(n, seed, null_product_rate=0.0):
    r = np.random.default_rng(seed)
    start = np.datetime64("2023-06-01")
    df = pd.DataFrame({
        "ts": (start + r.integers(0, 60, n).astype("timedelta64[D]")
               ).astype("datetime64[ns]"),
        "region": r.choice(["east", "west", "north"], n),
        "product": r.choice([f"p{i}" for i in range(8)], n),
        "qty": r.integers(0, 50, n),
        "price": np.round(r.uniform(1, 9, n), 2),
    })
    if null_product_rate:
        df.loc[df.sample(frac=null_product_rate,
                         random_state=seed).index, "product"] = None
    return df


INGEST = dict(time_column="ts", dimensions=["region", "product"],
              metrics=["qty", "price"])

QS = [
    "select region, sum(qty) as q, count(*) as n from sales "
    "group by region order by region",
    "select product, sum(price) as p from sales "
    "where region = 'east' group by product order by product",
    "select count(*) as n from sales where product is null",
]


def test_append_differential_vs_batch_ingest():
    batches = [_batch(500, 1), _batch(300, 2, 0.1), _batch(200, 3)]
    ctx_a = sdot.Context()
    for b in batches:
        ctx_a.stream_ingest("sales", b, **INGEST)
    ctx_b = sdot.Context()
    ctx_b.ingest_dataframe(
        "sales", pd.concat(batches, ignore_index=True), **INGEST)
    for q in QS:
        assert_frames_equal(ctx_a.sql(q).to_pandas(),
                            ctx_b.sql(q).to_pandas())


def test_empty_batch_is_noop():
    ctx = sdot.Context()
    ds0 = ctx.stream_ingest("sales", _batch(100, 4), **INGEST)
    v0 = ctx.store.datasource_version("sales")
    ds1 = ctx.stream_ingest("sales", _batch(0, 5).iloc[0:0], **INGEST)
    assert ds1 is ds0                       # same object: nothing changed
    assert ctx.store.datasource_version("sales") == v0   # no version bump


def test_empty_batch_writes_no_wal_record(tmp_path):
    ctx = sdot.Context({"sdot.persist.path": str(tmp_path)})
    ctx.stream_ingest("sales", _batch(50, 6), **INGEST)
    appends0 = ctx.persist.counters["wal_appends"]
    ctx.stream_ingest("sales", _batch(10, 7).iloc[0:0], **INGEST)
    assert ctx.persist.counters["wal_appends"] == appends0
    ctx.close()


def test_all_null_dim_column_append():
    ctx = sdot.Context()
    base = _batch(60, 8)
    ctx.stream_ingest("sales", base, **INGEST)
    nb = _batch(40, 9)
    nb["product"] = None
    ctx.stream_ingest("sales", nb, **INGEST)
    got = ctx.sql("select count(*) as n from sales "
                  "where product is null").to_pandas()
    assert int(got["n"][0]) == 40
    # and the reverse: a base whose dim starts all-null gains values
    ctx2 = sdot.Context()
    b0 = _batch(30, 10)
    b0["product"] = None
    ctx2.stream_ingest("t", b0, **INGEST)
    ctx2.stream_ingest("t", _batch(20, 11), **INGEST)
    got = ctx2.sql("select count(*) as n from t "
                   "where product is not null").to_pandas()
    assert int(got["n"][0]) == 20


def test_all_null_metric_column_append():
    ctx = sdot.Context()
    ctx.stream_ingest("sales", _batch(50, 12), **INGEST)
    nb = _batch(25, 13)
    nb["qty"] = None
    ctx.stream_ingest("sales", nb, **INGEST)
    got = ctx.sql("select count(qty) as n, count(*) as m "
                  "from sales").to_pandas()
    assert int(got["n"][0]) == 50 and int(got["m"][0]) == 75


def test_missing_column_appends_as_null():
    ctx = sdot.Context()
    ctx.stream_ingest("sales", _batch(40, 14), **INGEST)
    ctx.stream_ingest("sales", _batch(10, 15).drop(columns=["price"]),
                      **INGEST)
    got = ctx.sql("select count(price) as n, count(*) as m "
                  "from sales").to_pandas()
    assert int(got["n"][0]) == 40 and int(got["m"][0]) == 50


def test_unknown_column_rejected():
    ctx = sdot.Context()
    ds = ctx.stream_ingest("sales", _batch(20, 16), **INGEST)
    bad = _batch(5, 17)
    bad["surprise"] = 1
    with pytest.raises(ValueError, match="surprise"):
        append_dataframe(ds, bad)


def test_dictionary_merge_remaps_old_codes():
    ctx = sdot.Context()
    b1 = _batch(50, 18)
    b1["region"] = np.random.default_rng(18).choice(["m", "z"], 50)
    ctx.stream_ingest("sales", b1, **INGEST)
    b2 = _batch(50, 19)
    b2["region"] = np.random.default_rng(19).choice(["a", "q"], 50)
    ctx.stream_ingest("sales", b2, **INGEST)
    ds = ctx.store.get("sales")
    d = ds.dims["region"]
    assert list(d.dictionary) == sorted(d.dictionary)  # stays sorted
    # order-preserving codes: range pushdown must still be right
    got = ctx.sql("select count(*) as n from sales "
                  "where region > 'l'").to_pandas()
    want = int((pd.concat([b1, b2])["region"] > "l").sum())
    assert int(got["n"][0]) == want


def test_metric_dtype_widens_on_append():
    ctx = sdot.Context()
    b1 = _batch(30, 20)
    b1["qty"] = np.arange(30, dtype=np.int64)          # narrow
    ctx.stream_ingest("sales", b1, **INGEST)
    assert ctx.store.get("sales").metrics["qty"].values.dtype.itemsize <= 2
    b2 = _batch(10, 21)
    b2["qty"] = np.int64(3_000_000_000) + np.arange(10)  # needs int64
    ctx.stream_ingest("sales", b2, **INGEST)
    assert ctx.store.get("sales").metrics["qty"].values.dtype == np.int64
    got = ctx.sql("select max(qty) as m from sales").to_pandas()
    assert int(got["m"][0]) == 3_000_000_009


def test_append_bumps_version_and_marks_rollup_stale():
    ctx = sdot.Context()
    ctx.stream_ingest("sales", _batch(80, 22), **INGEST)
    ctx.sql("create rollup s_r on sales dimensions (region) "
            "aggregations (sum(qty))")
    v0 = ctx.store.datasource_version("sales")
    ctx.stream_ingest("sales", _batch(20, 23), **INGEST)
    assert ctx.store.datasource_version("sales") > v0
    rv = ctx.sql("select fresh from sys_rollups").to_pandas()
    assert bool(rv["fresh"][0]) is False


def test_append_racing_checkpoint(tmp_path):
    """Concurrent appends and checkpoints must serialize under the
    manager lock: every committed batch lands exactly once, and the
    final on-disk state recovers to the final in-memory state."""
    ctx = sdot.Context({"sdot.persist.path": str(tmp_path)})
    ctx.stream_ingest("sales", _batch(100, 24), **INGEST)
    errors = []
    stop = threading.Event()

    def checkpoints():
        while not stop.is_set():
            try:
                ctx.checkpoint("sales")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    t = threading.Thread(target=checkpoints)
    t.start()
    try:
        for i in range(10):
            ctx.stream_ingest("sales", _batch(20, 100 + i), **INGEST)
    finally:
        stop.set()
        t.join()
    assert not errors
    want = ctx.sql("select region, sum(qty) as q, count(*) as n "
                   "from sales group by region order by region").to_pandas()
    assert int(want["n"].sum()) == 300
    ctx.close()

    ctx2 = sdot.Context({"sdot.persist.path": str(tmp_path)})
    got = ctx2.sql("select region, sum(qty) as q, count(*) as n "
                   "from sales group by region order by region").to_pandas()
    assert_frames_equal(got, want)
    ctx2.close()


def test_append_without_time_column():
    ctx = sdot.Context()
    df1 = pd.DataFrame({"k": ["a", "b"], "v": [1, 2]})
    ctx.stream_ingest("kv", df1, dimensions=["k"], metrics=["v"])
    ctx.stream_ingest("kv", pd.DataFrame({"k": ["c"], "v": [9]}),
                      dimensions=["k"], metrics=["v"])
    got = ctx.sql("select k, sum(v) as v from kv "
                  "group by k order by k").to_pandas()
    assert list(got["k"]) == ["a", "b", "c"]
    assert list(got["v"]) == [1, 2, 9]
