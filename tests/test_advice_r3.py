"""Round-3 advisor findings, regression-locked (ADVICE.md r3).

1. low — tailprobe's command channel lives in a private 0700 dir, not a
   fixed world-writable /tmp path (local-user code-exec hazard).
2. low — EXPLAIN's late-materialization line is labelled an estimate
   (the execution-time decision additionally sees routes/sharding).
3. low — the staged-filter split and int_set_membership share ONE
   "lowers to a compare chain?" predicate: large near-contiguous sets
   are NOT staged; small scattered sets ARE.
4. low — the per-datasource pattern-selectivity cache is a bounded LRU.
5. low — negative plan-cache entries are a dedicated type, never a
   structural tuple sentinel.
"""

import os

import numpy as np
import pandas as pd
import pytest

import spark_druid_olap_tpu as sdot
from spark_druid_olap_tpu.ir import expr as E
from spark_druid_olap_tpu.ir import spec as S
from spark_druid_olap_tpu.ops import expr_compile as EC
from spark_druid_olap_tpu.parallel import cost as C
from spark_druid_olap_tpu.parallel.executor import QueryEngine


# -- 1. probe channel is private ---------------------------------------------

def test_tailprobe_channel_is_private(tmp_path, monkeypatch):
    monkeypatch.setenv("SDOT_PROBE_DIR", str(tmp_path / "probe"))
    import importlib
    import tools.tailprobe as tp
    importlib.reload(tp)
    d = tp.probe_dir()
    assert d == str(tmp_path / "probe")
    assert (os.stat(d).st_mode & 0o777) == 0o700
    assert os.stat(d).st_uid == os.getuid()
    assert tp.CMD.startswith(d) and tp.OUT.startswith(d)
    assert not tp.CMD.startswith("/tmp/sdot_probe")


def test_tailprobe_rejects_foreign_dir(tmp_path, monkeypatch):
    target = tmp_path / "target"
    target.mkdir()
    link = tmp_path / "link"
    link.symlink_to(target)
    monkeypatch.setenv("SDOT_PROBE_DIR", str(link))
    import importlib
    import tools.tailprobe as tp
    with pytest.raises(RuntimeError, match="symlink"):
        importlib.reload(tp)
    # restore a sane module state for other tests
    monkeypatch.delenv("SDOT_PROBE_DIR")
    importlib.reload(tp)


# -- 3. shared chain-lowering predicate --------------------------------------

def _staged(vals) -> bool:
    f = S.InFilter("x", E.FrozenIntSet(np.asarray(sorted(vals), np.int64)))
    cheap, exp = QueryEngine._split_filter_staged(f)
    return exp is not None


def test_staged_split_matches_chain_lowering():
    # large but near-contiguous: one run -> compare chain -> NOT staged
    contiguous = list(range(1000, 1200))
    assert EC.int_set_lowers_to_chain(np.asarray(contiguous, np.int64))
    assert not _staged(contiguous)
    # small but scattered (30 singleton runs > _CHAIN_MAX_RANGES, span
    # 30x the count): lowers as a gather -> IS staged
    scattered = [i * 1000 for i in range(30)]
    assert not EC.int_set_lowers_to_chain(np.asarray(scattered, np.int64))
    assert _staged(scattered)
    # tiny scattered set (<= 24 runs): chain again -> NOT staged
    tiny = [i * 1000 for i in range(20)]
    assert EC.int_set_lowers_to_chain(np.asarray(tiny, np.int64))
    assert not _staged(tiny)


def test_chain_predicate_agrees_with_membership_lowering():
    """int_set_runs is the single source of truth: when it yields runs,
    membership compiles without any gather (verified by lowering to HLO
    and asserting no gather/while appears)."""
    import jax

    vals = np.asarray(list(range(100, 400)), np.int64)  # one dense run
    assert EC.int_set_lowers_to_chain(vals)

    def f(x):
        return EC.int_set_membership(x, vals)

    txt = jax.jit(f).lower(
        jax.ShapeDtypeStruct((128,), np.int32)).as_text()
    assert "gather" not in txt and "while" not in txt


# -- 2 + 4. pattern cache bound / explain estimate label ---------------------

def test_pattern_frac_cache_is_bounded():
    df = pd.DataFrame({
        "d": pd.Series(["apple", "banana", "cherry", "date"] * 4,
                       dtype="object"),
        "m": np.arange(16.0),
    })
    ctx = sdot.Context()
    ds = ctx.ingest_dataframe("pat", df)
    for i in range(C._PATTERN_FRAC_BOUND + 50):
        f = S.PatternFilter("d", "contains", f"pfx{i}")
        C._pattern_fraction(f, ds)
    assert len(ds._pattern_frac_cache) <= C._PATTERN_FRAC_BOUND
    # hot entries survive: re-touch one, insert more, it stays
    f0 = S.PatternFilter("d", "contains", "apple")
    C._pattern_fraction(f0, ds)
    for i in range(C._PATTERN_FRAC_BOUND - 1):
        C._pattern_fraction(S.PatternFilter("d", "contains", f"z{i}"), ds)
    assert ("d", "contains", "apple") in ds._pattern_frac_cache


def test_explain_compaction_line_is_estimate():
    rng = np.random.default_rng(0)
    n = 40_000
    df = pd.DataFrame({
        "k": rng.integers(0, 50, n).astype(str),
        "sel": rng.integers(0, 100, n),
        "v": rng.normal(size=n),
    })
    ctx = sdot.Context(config={"sdot.engine.scan.compact.min.rows": 0})
    ctx.ingest_dataframe("exp_est", df)
    txt = ctx.explain(
        "select k, sum(v) from exp_est where sel < 3 group by k")
    if "late-materialize" in txt:
        assert "(estimate)" in txt


# -- 5. negative plan-cache entries are a dedicated type ---------------------

def test_negative_plan_entry_not_tuple_sentinel():
    from spark_druid_olap_tpu.planner import host_exec
    from spark_druid_olap_tpu.sql.session import _NegativePlan

    df = pd.DataFrame({"k": ["a", "b"], "v": [1.0, 2.0]})
    ctx = sdot.Context()
    ctx.ingest_dataframe("neg", df)
    # a statement the builder deterministically rejects: a session
    # Python UDF has no device compilation path (a plain equi self-join
    # now runs ENGINE mode via the round-5 disambiguation + composite
    # pushdown, so it no longer demotes)
    ctx.functions["negfn"] = lambda a, b: float(a) + float(b)
    sql = ("select k, count(*) as n from neg where negfn(v, v) > 0 "
           "group by k order by k")
    r1 = ctx.sql(sql)
    assert ctx.history.entries()[-1].stats["mode"].startswith("host")
    plan_cache = getattr(ctx, "_result_cache", {}).get("plan", {})
    negs = [v for v in plan_cache.values() if isinstance(v, _NegativePlan)]
    tuples = [v for v in plan_cache.values() if isinstance(v, tuple)]
    assert negs, "expected a negative plan-cache entry"
    assert not tuples, "bare-tuple sentinel must be gone"
    # second run hits the negative entry and still answers identically
    r2 = ctx.sql(sql)
    assert r1.to_pandas().equals(r2.to_pandas())
