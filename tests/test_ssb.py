"""SSB 13-query suite — differential tests against pandas oracles on the
flat frame, plus plan assertions that every query collapses onto the flat
index and pushes down (the whole point of SSB for this engine)."""

import numpy as np
import pandas as pd
import pytest

import spark_druid_olap_tpu as sdot
from spark_druid_olap_tpu.tools import ssb


@pytest.fixture(scope="module")
def env():
    ctx = sdot.Context()
    tables, flat = ssb.setup_context(ctx, sf=0.003, target_rows=4096)
    return ctx, flat


def run(ctx, name):
    r = ctx.sql(ssb.QUERIES[name]).to_pandas()
    mode = ctx.history.entries()[-1].stats["mode"]
    return r, mode


def test_all_13_push_down_and_run(env):
    ctx, flat = env
    for name in ssb.QUERIES:
        r, mode = run(ctx, name)
        assert mode == "engine", f"{name} fell back: {mode}"


def test_q1_1_oracle(env):
    ctx, flat = env
    got, mode = run(ctx, "q1.1")
    m = (flat.d_year == 1993) & flat.lo_discount.between(1, 3) & \
        (flat.lo_quantity < 25)
    want = (flat.lo_extendedprice[m] * flat.lo_discount[m]).sum()
    np.testing.assert_allclose(float(got["revenue"][0]), want, rtol=1e-6)


def test_q2_1_oracle(env):
    ctx, flat = env
    got, _ = run(ctx, "q2.1")
    m = (flat.p_category == "MFGR#12") & (flat.s_region == "AMERICA")
    want = flat[m].groupby(["d_year", "p_brand1"]).lo_revenue.sum() \
        .reset_index().sort_values(["d_year", "p_brand1"]) \
        .reset_index(drop=True)
    assert list(got["d_year"]) == list(want["d_year"])
    assert list(got["p_brand1"]) == list(want["p_brand1"])
    np.testing.assert_allclose(got["lo_revenue"], want["lo_revenue"],
                               rtol=1e-5)


def test_q3_1_oracle(env):
    ctx, flat = env
    got, _ = run(ctx, "q3.1")
    m = (flat.c_region == "ASIA") & (flat.s_region == "ASIA") & \
        flat.d_year.between(1992, 1997)
    want = flat[m].groupby(["c_nation", "s_nation", "d_year"]) \
        .lo_revenue.sum().reset_index()
    assert len(got) == len(want)
    gm = got.set_index(["c_nation", "s_nation", "d_year"]).lo_revenue
    for _, row in want.iterrows():
        np.testing.assert_allclose(
            gm[(row.c_nation, row.s_nation, row.d_year)], row.lo_revenue,
            rtol=1e-5)


def test_q4_1_oracle(env):
    ctx, flat = env
    got, _ = run(ctx, "q4.1")
    m = (flat.c_region == "AMERICA") & (flat.s_region == "AMERICA") & \
        flat.p_mfgr.isin(["MFGR#1", "MFGR#2"])
    want = flat[m].assign(pf=flat.lo_revenue - flat.lo_supplycost) \
        .groupby(["d_year", "c_nation"]).pf.sum().reset_index() \
        .sort_values(["d_year", "c_nation"]).reset_index(drop=True)
    assert list(got["d_year"]) == list(want["d_year"])
    assert list(got["c_nation"]) == list(want["c_nation"])
    np.testing.assert_allclose(got["profit"], want["pf"], rtol=1e-5)


def test_q3_4_empty_or_small(env):
    ctx, flat = env
    got, mode = run(ctx, "q3.4")
    m = (flat.c_city.isin(["UNITED KI1", "UNITED KI5"])
         & flat.s_city.isin(["UNITED KI1", "UNITED KI5"])
         & (flat.d_yearmonth == "Dec1997"))
    assert len(got) == len(
        flat[m].groupby(["c_city", "s_city", "d_year"]).size())
